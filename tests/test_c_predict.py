"""C predict ABI tests (src/c_predict_api.cc, parity:
include/mxnet/c_predict_api.h).

Two modes: (1) ctypes loads the library into this interpreter (the ABI
joins the running CPython); (2) a standalone C program embeds a fresh
interpreter — the reference deployment shape for non-Python hosts."""
import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB = os.path.join(_REPO, "src", "build", "libmxnet_tpu_predict.so")


def _build_lib():
    if os.path.exists(_LIB):
        return True
    try:
        subprocess.run(["make", "-C", os.path.join(_REPO, "src"),
                        "predict"], check=True, capture_output=True,
                       timeout=180)
        return os.path.exists(_LIB)
    except Exception:
        return False


needs_lib = pytest.mark.skipif(not _build_lib(),
                               reason="predict library not buildable")


def _export_mlp(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(2, 4).astype(np.float32))
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "mlp")
    net.export(prefix)
    return prefix, x.asnumpy(), ref


def _bind_api(lib):
    u32 = ctypes.c_uint32
    lib.MXPredCreate.restype = ctypes.c_int
    lib.MXPredCreate.argtypes = [
        ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, u32, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(u32), ctypes.POINTER(u32),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.MXPredSetInput.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_float), u32]
    lib.MXPredForward.argtypes = [ctypes.c_void_p]
    lib.MXPredGetOutputShape.argtypes = [
        ctypes.c_void_p, u32, ctypes.POINTER(ctypes.POINTER(u32)),
        ctypes.POINTER(u32)]
    lib.MXPredGetOutput.argtypes = [ctypes.c_void_p, u32,
                                    ctypes.POINTER(ctypes.c_float), u32]
    lib.MXPredFree.argtypes = [ctypes.c_void_p]
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


@needs_lib
def test_ctypes_roundtrip(tmp_path):
    prefix, xin, ref = _export_mlp(tmp_path)
    sym_json = open(prefix + "-symbol.json").read().encode()
    params = open(prefix + "-0000.params", "rb").read()

    lib = _bind_api(ctypes.CDLL(_LIB))
    handle = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    shape = (ctypes.c_uint32 * 2)(2, 4)
    rc = lib.MXPredCreate(sym_json, params, len(params), 1, 0, 1, keys,
                          indptr, shape, ctypes.byref(handle))
    assert rc == 0, lib.MXGetLastError()

    data = np.ascontiguousarray(xin, np.float32)
    rc = lib.MXPredSetInput(
        handle, b"data",
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), data.size)
    assert rc == 0, lib.MXGetLastError()
    assert lib.MXPredForward(handle) == 0, lib.MXGetLastError()

    sd = ctypes.POINTER(ctypes.c_uint32)()
    ndim = ctypes.c_uint32()
    assert lib.MXPredGetOutputShape(handle, 0, ctypes.byref(sd),
                                    ctypes.byref(ndim)) == 0
    out_shape = tuple(sd[i] for i in range(ndim.value))
    assert out_shape == (2, 3)

    out = np.zeros(out_shape, np.float32)
    rc = lib.MXPredGetOutput(
        handle, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size)
    assert rc == 0, lib.MXGetLastError()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    lib.MXPredFree(handle)


_C_MAIN = r"""
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* PredictorHandle;
typedef unsigned int mx_uint;
extern int MXPredCreate(const char*, const void*, int, int, int, mx_uint,
                        const char**, const mx_uint*, const mx_uint*,
                        PredictorHandle*);
extern int MXPredSetInput(PredictorHandle, const char*, const float*,
                          mx_uint);
extern int MXPredForward(PredictorHandle);
extern int MXPredGetOutput(PredictorHandle, mx_uint, float*, mx_uint);
extern int MXPredFree(PredictorHandle);
extern const char* MXGetLastError();

static char* slurp(const char* path, long* size) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END); *size = ftell(f); fseek(f, 0, SEEK_SET);
  char* buf = malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) { fclose(f); return NULL; }
  buf[*size] = 0; fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  long sym_size, param_size;
  char* sym = slurp(argv[1], &sym_size);
  char* params = slurp(argv[2], &param_size);
  if (!sym || !params) return 2;
  const char* keys[1] = {"data"};
  mx_uint indptr[2] = {0, 2};
  mx_uint shape[2] = {2, 4};
  PredictorHandle h;
  if (MXPredCreate(sym, params, (int)param_size, 1, 0, 1, keys, indptr,
                   shape, &h) != 0) {
    fprintf(stderr, "create: %s\n", MXGetLastError());
    return 3;
  }
  float in[8];
  for (int i = 0; i < 8; ++i) in[i] = (float)i * 0.25f - 1.0f;
  if (MXPredSetInput(h, "data", in, 8) != 0) return 4;
  if (MXPredForward(h) != 0) {
    fprintf(stderr, "fwd: %s\n", MXGetLastError());
    return 5;
  }
  float out[6];
  if (MXPredGetOutput(h, 0, out, 6) != 0) return 6;
  for (int i = 0; i < 6; ++i) printf("%.6f\n", out[i]);
  MXPredFree(h);
  return 0;
}
"""


@needs_lib
def test_standalone_c_program(tmp_path):
    """True embedding: a C binary (no Python host) drives inference."""
    prefix, _xin, _ref = _export_mlp(tmp_path)
    c_src = tmp_path / "main.c"
    c_src.write_text(_C_MAIN)
    exe = str(tmp_path / "predict_demo")
    try:
        subprocess.run(
            ["gcc", str(c_src), "-o", exe,
             f"-L{os.path.dirname(_LIB)}", "-lmxnet_tpu_predict",
             f"-Wl,-rpath,{os.path.dirname(_LIB)}"],
            check=True, capture_output=True, timeout=120)
    except Exception:
        pytest.skip("no C toolchain for the standalone binary")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    site = [p for p in sys.path if "site-packages" in p]
    env["PYTHONPATH"] = os.pathsep.join([_REPO] + site)
    proc = subprocess.run(
        [exe, prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr
    got = np.asarray([float(x) for x in proc.stdout.split()],
                     np.float32).reshape(2, 3)
    # python-side reference with the same fixed input
    xin = (np.arange(8, dtype=np.float32) * 0.25 - 1.0).reshape(2, 4)
    from mxnet_tpu.c_predict import Predictor
    p = Predictor(open(prefix + "-symbol.json").read(),
                  open(prefix + "-0000.params", "rb").read(),
                  {"data": (2, 4)})
    p.set_input("data", xin.tobytes())
    p.forward()
    ref = np.frombuffer(p.output_bytes(0), np.float32).reshape(2, 3)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


_CPP_MAIN = r"""
#include <mxnet_tpu/predictor.hpp>
#include <cstdio>
#include <fstream>
#include <sstream>

static std::string slurp(const char* p) {
  std::ifstream f(p, std::ios::binary);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

int main(int argc, char** argv) {
  (void)argc;
  mxnet_tpu::Predictor pred(slurp(argv[1]), slurp(argv[2]),
                            {{"data", {2, 4}}});
  std::vector<float> in(8);
  for (int i = 0; i < 8; ++i) in[i] = i * 0.25f - 1.0f;
  pred.SetInput("data", in.data(), in.size());
  pred.Forward();
  auto shape = pred.GetOutputShape(0);
  if (shape.size() != 2 || shape[0] != 2 || shape[1] != 3) return 7;
  for (float v : pred.GetOutput(0)) std::printf("%.6f\n", v);
  return 0;
}
"""


@needs_lib
def test_cpp_package_wrapper(tmp_path):
    """Header-only C++ fluent API (cpp-package/) over the C ABI."""
    prefix, _xin, _ref = _export_mlp(tmp_path)
    cpp = tmp_path / "main.cc"
    cpp.write_text(_CPP_MAIN)
    exe = str(tmp_path / "cpp_demo")
    inc = os.path.join(_REPO, "cpp-package", "include")
    try:
        subprocess.run(
            ["g++", "-std=c++17", str(cpp), "-o", exe, f"-I{inc}",
             f"-L{os.path.dirname(_LIB)}", "-lmxnet_tpu_predict",
             f"-Wl,-rpath,{os.path.dirname(_LIB)}"],
            check=True, capture_output=True, timeout=120)
    except subprocess.CalledProcessError as e:
        pytest.fail(f"cpp compile failed: {e.stderr.decode()[-2000:]}")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    site = [p for p in sys.path if "site-packages" in p]
    env["PYTHONPATH"] = os.pathsep.join([_REPO] + site)
    proc = subprocess.run(
        [exe, prefix + "-symbol.json", prefix + "-0000.params"],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr
    got = np.asarray([float(x) for x in proc.stdout.split()], np.float32)
    assert got.shape == (6,) and np.isfinite(got).all()
