"""Numerics observatory (ISSUE 14): in-trace training-health telemetry,
non-finite sentinels, anomaly forensics.

Acceptance surface:

* armed windows change NOTHING — weights bitwise-identical with
  MXNET_NUMERICS on vs off (SGD / momentum / Adam, K=8 scan and a
  dp×tp mesh) and dispatches/step unchanged;
* a ``train/poison_grad`` chaos injection is detected within one
  window, drives the default-pack ``nonfinite_window`` alert
  pending→firing (visible in /alerts.json), lands in the flight ring,
  and writes a forensic dump naming the poisoned window;
* skip mode continues training past one poisoned window bit-identically
  to a manual skip; halt mode raises typed ``NonFiniteError``;
* the serving output-health guard fails non-finite rows typed, never
  serves them, and the pool keeps answering healthy requests;
* installing a legacy Monitor still opts out of fusion, with
  ``monitor.numerics_summary()`` as the fused-compatible alternative.
"""
import glob
import json
import os
import urllib.request

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import io as mxio
from mxnet_tpu import profiler as prof
from mxnet_tpu.base import NonFiniteError
from mxnet_tpu.chaos import failpoints as chaos
from mxnet_tpu.telemetry import flight, numerics

_ENV_KEYS = ("MXNET_FUSED_STEP", "MXNET_SCAN_STEPS", "MXNET_NUMERICS",
             "MXNET_NUMERICS_GRAD_NORM_MAX", "MXNET_MESH_FUSED_STEP")


@pytest.fixture(autouse=True)
def _numerics_env(tmp_path, monkeypatch):
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    monkeypatch.setenv("MXNET_NUMERICS_DUMP_DIR", str(tmp_path))
    chaos.reset()
    numerics._reset_for_tests()
    yield
    chaos.reset()
    numerics._reset_for_tests()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    numerics.configure()


def _mlp():
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(d, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _init_params(seed=5):
    rng = np.random.RandomState(seed)
    return {"fc1_weight": mx.nd.array(rng.randn(32, 20) * 0.1),
            "fc1_bias": mx.nd.zeros((32,)),
            "fc2_weight": mx.nd.array(rng.randn(10, 32) * 0.1),
            "fc2_bias": mx.nd.zeros((10,))}


def _dataset(n, feat=20, seed=3):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, feat).astype(np.float32),
            rng.randint(0, 10, n).astype(np.float32))


def _fit(mode, x, y, scan_steps=8, optimizer="sgd", opt_params=None,
         pre_keys=0, batch_size=16):
    os.environ["MXNET_FUSED_STEP"] = "1"
    os.environ["MXNET_SCAN_STEPS"] = str(scan_steps)
    os.environ["MXNET_NUMERICS"] = mode
    numerics.configure()
    mx.random.seed(0)
    from mxnet_tpu import random as mxrand
    for _ in range(pre_keys):
        mxrand.next_key()
    it = mxio.NDArrayIter(mx.nd.array(x), mx.nd.array(y),
                          batch_size=batch_size,
                          label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer=optimizer,
            optimizer_params=opt_params or {"learning_rate": 0.05},
            arg_params={k: v.copy() for k, v in _init_params().items()})
    params, _ = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in params.items()}


def _opt_state_leaves(mod):
    import pickle
    states = pickle.loads(mod.get_optimizer_states())
    leaves = {}
    for i in states:
        s = states[i] if isinstance(states[i], tuple) else (states[i],)
        leaves[i] = [x.asnumpy() for x in s if x is not None]
    return leaves


# -- parity: armed observation changes nothing -------------------------------
@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.05}),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01, "wd": 1e-4}),
])
def test_armed_scan_bitwise_parity(optimizer, opt_params):
    """MXNET_NUMERICS=warn over a K=8 scanned fit: weights AND optimizer
    state bitwise-identical to numerics-off, dispatches/step unchanged
    (the stats ride the existing donated window)."""
    x, y = _dataset(256)  # 16 batches -> 2 windows of K=8
    prof.reset_dispatch_counts()
    m_off, p_off = _fit("off", x, y, optimizer=optimizer,
                        opt_params=dict(opt_params))
    d_off = prof.dispatch_counts().get("total", 0)
    numerics._reset_for_tests()
    prof.reset_dispatch_counts()
    m_on, p_on = _fit("warn", x, y, optimizer=optimizer,
                      opt_params=dict(opt_params))
    d_on = prof.dispatch_counts().get("total", 0)
    assert d_on == d_off, "armed numerics changed the dispatch count"
    for k in p_off:
        assert np.array_equal(p_off[k], p_on[k]), f"param {k} diverged"
    ls, lq = _opt_state_leaves(m_on), _opt_state_leaves(m_off)
    for i in ls:
        for a, b in zip(ls[i], lq[i]):
            assert np.array_equal(a, b), f"optimizer state {i} diverged"
    s = numerics.summary()
    assert s["steps"] == 16 and s["nonfinite_windows"] == 0
    # the in-trace stats landed in the history with sane values
    last = numerics.history()[-1]
    assert last["kind"] == "scan_window"
    assert np.isfinite(last["grad_norm"]) and last["grad_norm"] > 0
    assert np.isfinite(last["param_norm"]) and last["param_norm"] > 0
    assert last["update_ratio"] > 0  # window-cadence slot, last row
    assert last["nonfinite"] == 0


def test_armed_single_fused_step_parity():
    """K=1 (plain fused step): parity + per-step observation."""
    x, y = _dataset(64)
    _m, p_off = _fit("off", x, y, scan_steps=1)
    numerics._reset_for_tests()
    _m, p_on = _fit("warn", x, y, scan_steps=1)
    for k in p_off:
        assert np.array_equal(p_off[k], p_on[k]), f"param {k} diverged"
    s = numerics.summary()
    assert s["steps"] == 4
    assert numerics.history()[-1]["kind"] == "fused_step"


def test_armed_mesh_bitwise_parity():
    """MXNET_NUMERICS=warn under the dp×tp mesh-fused window: weights
    bitwise-identical to off, mesh dispatches unchanged."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    from mxnet_tpu.parallel import fused as F
    build, init, rng = F._mesh_models()
    K, NB, BS = 4, 8, 16
    x = rng.randn(NB * BS, 50).astype(np.float32)
    y = rng.randint(0, 10, NB * BS).astype(np.float32)
    opt = {"learning_rate": 0.1, "momentum": 0.9}
    os.environ["MXNET_NUMERICS"] = "off"
    numerics.configure()
    p_off, s_off, c_off, _w, _m = F._run_mesh_fit(
        K, NB, BS, "sgd", opt, build, init, x, y)
    os.environ["MXNET_NUMERICS"] = "warn"
    numerics.configure()
    p_on, s_on, c_on, _w, _m = F._run_mesh_fit(
        K, NB, BS, "sgd", opt, build, init, x, y)
    assert c_on.get("mesh_window") == c_off.get("mesh_window") == NB // K
    assert c_on.get("total") == c_off.get("total")
    for k in p_off:
        np.testing.assert_array_equal(p_off[k], p_on[k], err_msg=k)
    for i in s_off:
        for a, b in zip(F._state_arrays(s_on[i]),
                        F._state_arrays(s_off[i])):
            np.testing.assert_array_equal(a, b, err_msg=f"state {i}")
    assert numerics.summary()["steps"] == NB
    assert numerics.history()[-1]["kind"] == "mesh_window"


# -- detection: poison -> alert + flight + forensics -------------------------
def test_poison_detected_with_alert_flight_and_dump(tmp_path):
    """The acceptance gate: a train/poison_grad injection is detected
    within one window, drives the default-pack nonfinite_window rule
    pending->firing (visible in /alerts.json), lands in the flight
    ring, and writes a forensic dump naming the poisoned window."""
    from mxnet_tpu.telemetry import alerts
    from mxnet_tpu.telemetry.alerts import AlertEngine
    from mxnet_tpu.telemetry.exporter import start_exporter, stop_exporter

    flight.enable()
    flight.clear()
    eng = AlertEngine()  # the DEFAULT pack, real registry sampler
    alerts.set_engine(eng)
    try:
        x, y = _dataset(256)
        os.environ["MXNET_NUMERICS"] = "warn"
        numerics.configure()
        eng.tick(now=1.0)  # rate baseline BEFORE the poison
        chaos.arm("train/poison_grad", "raise", hits=2, count=1)
        _fit("warn", x, y)  # window 2 of 2 poisoned
        chaos.reset()
        s = numerics.summary()
        assert s["nonfinite_windows"] == 1, s

        # alert: pending -> firing on the very next tick (for_s=0)
        eng.tick(now=2.0)
        assert eng.state("nonfinite_window")["state"] == "firing"
        transitions = [t["to"] for t in
                       eng.transitions("nonfinite_window")]
        assert transitions[:2] == ["pending", "firing"]

        # visible in /alerts.json
        import mxnet_tpu.telemetry.alerts as alerts_mod
        orig_armed = alerts_mod._armed
        alerts_mod._armed = True
        port = start_exporter(0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/alerts.json",
                    timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert "nonfinite_window" in doc["firing"]
            assert "nonfinite_window" in doc["pages"]
        finally:
            alerts_mod._armed = orig_armed
            stop_exporter()

        # flight ring carries the detection event
        evs = [e for e in flight.events()
               if e["category"] == "numerics"
               and e["event"] == "nonfinite_window"]
        assert evs and evs[0]["severity"] == "error"
        assert evs[0]["fields"]["kind"] == "scan_window"

        # forensic dump names the poisoned window + evidence
        dumps = sorted(glob.glob(
            os.path.join(str(tmp_path), "mxnet-numerics-*.json")))
        assert dumps, "no forensic dump written"
        doc = json.load(open(dumps[0]))
        assert doc["verdict"] == "nonfinite"
        assert doc["window"] == 2 and doc["kind"] == "scan_window"
        assert doc["bad_step"] == 9  # first step of window 2
        assert doc["rng_key_path"] is not None
        assert doc["window_stats"] and doc["history"]
        assert doc["nonfinite_by_bucket"], "no bucket named"
    finally:
        alerts.set_engine(None)


def test_skip_mode_matches_manual_skip_bitwise():
    """Skip mode drops a poisoned window's updates ON DEVICE and
    continues bit-identically to a manual skip (same key stream, second
    window's batches only)."""
    x, y = _dataset(256)  # 2 windows of K=8
    chaos.arm("train/poison_grad", "raise", hits=1, count=1)
    m_a, p_a = _fit("skip", x, y)
    chaos.reset()
    s = numerics.summary()
    assert s["nonfinite_windows"] == 1 and s["skipped_updates"] == 8
    # manual-skip reference: consume window 1's 8 keys, train only on
    # window 2's batches, numerics off
    numerics._reset_for_tests()
    m_b, p_b = _fit("off", x[128:], y[128:], pre_keys=8)
    for k in p_a:
        assert np.array_equal(p_a[k], p_b[k]), f"param {k} diverged"
    ls, lq = _opt_state_leaves(m_a), _opt_state_leaves(m_b)
    for i in ls:
        for a, b in zip(ls[i], lq[i]):
            assert np.array_equal(a, b), f"optimizer state {i} diverged"


def test_halt_mode_raises_typed_nonfinite_error(tmp_path):
    """halt: the boundary check raises NonFiniteError carrying the
    poisoned step + dump path; the fit does NOT degrade into per-batch
    fallback steps."""
    x, y = _dataset(256)
    chaos.arm("train/poison_grad", "raise", hits=1, count=1)
    with pytest.raises(NonFiniteError) as ei:
        _fit("halt", x, y)
    assert ei.value.retryable is False
    assert ei.value.step == 1
    assert ei.value.dump_path and os.path.exists(ei.value.dump_path)


def test_grad_norm_max_rule_breach(monkeypatch):
    """MXNET_NUMERICS_GRAD_NORM_MAX: a finite window breaching the
    bound is judged rule_breach (flight event + dump, warn mode
    continues)."""
    monkeypatch.setenv("MXNET_NUMERICS_GRAD_NORM_MAX", "1e-6")
    flight.enable()
    flight.clear()
    x, y = _dataset(128)
    _fit("warn", x, y)
    s = numerics.summary()
    assert s["rule_breach_windows"] >= 1
    evs = [e for e in flight.events()
           if e["category"] == "numerics"
           and e["event"] == "grad_norm_breach"]
    assert evs


# -- serving output-health guard ---------------------------------------------
def test_serving_guard_fails_nonfinite_rows_typed():
    """A model producing NaN outputs fails THOSE requests typed
    (NonFiniteError, never served), bumps the serving counter, and the
    pool keeps serving healthy requests."""
    from mxnet_tpu import serving, telemetry

    sym = mx.sym.log(mx.sym.Variable("data"))  # negative input -> nan
    server = serving.ModelServer(max_batch_size=4, max_latency_ms=2.0,
                                 name="nf-unit")
    try:
        server.load("m", symbol=sym, params={})
        ok = server.predict("m", {"data": np.ones(3, np.float32)})
        assert np.allclose(np.asarray(ok[0]), 0.0)
        with pytest.raises(NonFiniteError):
            server.predict("m", {"data": -np.ones(3, np.float32)})
        # survivors keep serving
        again = server.predict("m", {"data": 2 * np.ones(3, np.float32)})
        assert np.allclose(np.asarray(again[0]), np.log(2.0))
        fam = telemetry.REGISTRY.get(
            "mxnet_numerics_serving_nonfinite_total")
        assert fam is not None
        assert sum(s[2] for s in fam._samples()) >= 1
        assert server.stats().get("nonfinite_total", 0) >= 1
    finally:
        server.shutdown()


def test_serving_guard_disabled_serves_raw(monkeypatch):
    """MXNET_NUMERICS_SERVING=0: the screen is off — non-finite rows
    resolve (documented escape hatch)."""
    from mxnet_tpu import serving
    monkeypatch.setenv("MXNET_NUMERICS_SERVING", "0")
    numerics.configure()
    sym = mx.sym.log(mx.sym.Variable("data"))
    server = serving.ModelServer(max_batch_size=4, max_latency_ms=2.0,
                                 name="nf-off")
    try:
        server.load("m", symbol=sym, params={})
        out = server.predict("m", {"data": -np.ones(3, np.float32)})
        assert np.isnan(np.asarray(out[0])).all()
    finally:
        server.shutdown()
        monkeypatch.delenv("MXNET_NUMERICS_SERVING")
        numerics.configure()


# -- legacy Monitor compatibility --------------------------------------------
def test_monitor_opts_out_of_fusion_and_numerics_is_the_alternative():
    """Documented contract: installing a Monitor keeps the per-op loop
    (no fused/scan engagement), and monitor.numerics_summary() serves
    Monitor.toc()-shaped rows from the fused-compatible observatory."""
    from mxnet_tpu import monitor as monitor_mod
    x, y = _dataset(64)
    os.environ["MXNET_FUSED_STEP"] = "1"
    os.environ["MXNET_SCAN_STEPS"] = "8"
    os.environ["MXNET_NUMERICS"] = "warn"
    numerics.configure()
    mx.random.seed(0)
    mon = monitor_mod.Monitor(interval=1, pattern="$^")  # match nothing
    it = mxio.NDArrayIter(mx.nd.array(x), mx.nd.array(y), batch_size=16,
                          label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    prof.reset_dispatch_counts()
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            arg_params={k: v.copy() for k, v in _init_params().items()},
            monitor=mon)
    counts = prof.dispatch_counts()
    assert counts.get("fused_step", 0) == 0, \
        "monitor did not opt the module out of the fused step"
    assert counts.get("scan_window", 0) == 0
    assert mod._fused is None and mod._scan is None
    # the monitored loop produced NO observatory rows (per-op path) —
    # now run fused without the monitor and read the summary
    numerics._reset_for_tests()
    _fit("warn", x, y)
    rows = monitor_mod.numerics_summary()
    assert rows, "numerics_summary is empty after an armed fit"
    step, stat, val = rows[-1]
    assert isinstance(step, int) and isinstance(val, str)
    assert stat in ("grad_norm", "param_norm", "update_ratio", "loss")
    stats_seen = {r[1] for r in rows}
    assert {"grad_norm", "param_norm", "update_ratio",
            "loss"} <= stats_seen


# -- plumbing ----------------------------------------------------------------
def test_stat_groups_contiguous_and_bounded():
    groups, labels = numerics.stat_groups(
        [(1 << 18,), (1 << 18,), (8,)], ["float32"] * 3,
        names=["a", "b", "c"], bucket_mb=1.0)
    # 1 MB each under a 1 MB budget -> one param per bucket + the tail
    assert groups == [[0], [1], [2]]
    assert labels == ["a", "b", "c"]
    groups, labels = numerics.stat_groups(
        [(8,), (8,), (8,)], ["float32", "float16", "float32"],
        names=["a", "b", "c"], bucket_mb=64)
    assert groups == [[0], [1], [2]]  # dtype boundary splits


def test_registry_families_and_collector():
    """Armed windows export the mxnet_numerics_* families (plain
    registry metrics: they ride the fleet push) and the collector
    snapshot."""
    from mxnet_tpu import telemetry
    x, y = _dataset(128)
    _fit("warn", x, y)
    dump = telemetry.prometheus_dump()
    for fam in ("mxnet_numerics_grad_norm", "mxnet_numerics_param_norm",
                "mxnet_numerics_update_ratio", "mxnet_numerics_loss",
                "mxnet_numerics_steps_total"):
        assert fam in dump, f"{fam} missing from the scrape"
    snap = telemetry.snapshot()["numerics"]
    assert snap["mode"] == "warn" and snap["steps"] >= 8


def test_bad_mode_rejected(monkeypatch):
    from mxnet_tpu.base import MXNetError
    monkeypatch.setenv("MXNET_NUMERICS", "loud")
    with pytest.raises(MXNetError):
        numerics.configure()


def test_disabled_boundary_check_is_cheap():
    """mode=off: observe_window is an early-out (< 1 us, the
    span/trace/failpoint bar — bench-gated too)."""
    import time
    assert not numerics.armed()
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        numerics.observe_window(None, "t", 0, 0)
    per = (time.perf_counter() - t0) / n
    assert per < 1e-6, f"disabled boundary check costs {per * 1e9:.0f} ns"


def test_loss_scaler_feed_from_window():
    """An attached LossScaler consumes the window's per-step flags:
    a poisoned window backs the scale off exactly like update_scale."""
    from mxnet_tpu.amp import LossScaler
    scaler = LossScaler(init_scale=2. ** 10, scale_window=1000)
    numerics.attach_loss_scaler(scaler)
    try:
        x, y = _dataset(256)
        chaos.arm("train/poison_grad", "raise", hits=1, count=1)
        _fit("skip", x, y)
        chaos.reset()
        # window 1: 8 poisoned steps halve 8 times; window 2 clean
        assert scaler.loss_scale == 2. ** 10 / 2 ** 8
    finally:
        numerics.detach_loss_scaler(scaler)
