"""2-bit gradient compression (reference:
src/kvstore/gradient_compression.h kTwoBit + error feedback;
tests/nightly/dist_sync_kvstore.py compressed push assertions)."""
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gradient_compression import GradientCompression, create


def test_quantize_codes_and_threshold():
    gc = GradientCompression(threshold=0.5)
    grad = np.array([0.7, -0.6, 0.1, -0.1, 0.0], np.float32)
    deq = gc.dequantize(gc.quantize("k", grad), grad.shape)
    np.testing.assert_allclose(deq, [0.5, -0.5, 0.0, 0.0, 0.0])


def test_error_feedback_residual_accumulates():
    gc = GradientCompression(threshold=0.5)
    grad = np.full((8,), 0.2, np.float32)
    # 0.2 < 0.5: first two pushes emit nothing, residual reaches 0.6
    d1 = gc.dequantize(gc.quantize("k", grad), grad.shape)
    d2 = gc.dequantize(gc.quantize("k", grad), grad.shape)
    d3 = gc.dequantize(gc.quantize("k", grad), grad.shape)
    np.testing.assert_allclose(d1, 0.0)
    np.testing.assert_allclose(d2, 0.0)
    np.testing.assert_allclose(d3, 0.5)  # residual 0.6 >= threshold
    # long-run mean approaches the true gradient (unbiased-ish drift)
    total = d1 + d2 + d3
    for _ in range(17):
        total = total + gc.dequantize(gc.quantize("k", grad), grad.shape)
    np.testing.assert_allclose(total / 20.0, 0.2, atol=0.03)


def test_packing_is_4_codes_per_byte():
    gc = GradientCompression(threshold=1.0)
    grad = np.ones((1000,), np.float32)
    packed = gc.quantize("k", grad)
    assert packed.dtype == np.uint8
    assert packed.size == 250
    np.testing.assert_allclose(gc.dequantize(packed, (1000,)), 1.0)


def test_create_validates():
    assert create({"type": "none"}) is None
    assert create({"type": "2bit", "threshold": 2.0}).threshold == 2.0
    with pytest.raises(MXNetError):
        create({"type": "1bit"})
    with pytest.raises(MXNetError):
        create({"type": "2bit", "bogus": 1})


def test_local_store_rejects_compression():
    kv = kvstore.create("local")
    with pytest.raises(MXNetError):
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})


def test_device_store_compressed_convergence():
    """Linear regression through a compressed 'device' kvstore still
    converges (error feedback recovers the small updates)."""
    rng = np.random.RandomState(0)
    w_true = rng.randn(4).astype(np.float32)
    w = nd.array(np.zeros(4, np.float32))
    kv = kvstore.create("device")
    # each step moves at most threshold*lr per coordinate, so the
    # constants must allow reaching |w_true|~1 within the step budget
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", w)
    lr = 0.1
    losses = []
    for step in range(150):
        x = rng.randn(32, 4).astype(np.float32)
        err = x @ np.asarray(w.asnumpy()) - x @ w_true
        losses.append(float((err ** 2).mean()))
        grad = nd.array((x.T @ err / 32).astype(np.float32))
        kv.push("w", grad)
        agg = nd.zeros(4)
        kv.pull("w", out=agg)   # no updater: store holds the deq grad
        w = nd.array(w.asnumpy() - lr * agg.asnumpy())
    assert np.mean(losses[-10:]) < losses[0] * 0.2, losses[::15]


_WORKER = """
import os, sys
rank, num_workers, port, out = (int(sys.argv[1]), int(sys.argv[2]),
                                int(sys.argv[3]), sys.argv[4])
os.environ["DMLC_RANK"] = str(rank)
os.environ["DMLC_NUM_WORKER"] = str(num_workers)
os.environ["DMLC_PS_ROOT_URI"] = "127.0.0.1"
os.environ["DMLC_PS_ROOT_PORT"] = str(port)
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import kvstore, nd
kv = kvstore.create("dist_sync")
kv.set_gradient_compression({"type": "2bit", "threshold": 0.25})
kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0, wd=0.0))
w = nd.array(np.zeros(8, np.float32))
kv.init("w", w)
# every worker pushes the same grad pattern; with threshold .25 the
# elements 0..3 (value .3) quantize to .25 each push, elements 4..7
# (value .1) emit only when the residual crosses the threshold
grad = nd.array(np.array([0.3]*4 + [0.1]*4, np.float32))
for step in range(6):
    kv.push("w", grad)
    out_arr = nd.zeros(8)
    kv.pull("w", out=out_arr)
np.save(out, out_arr.asnumpy())
"""


def test_dist_sync_4workers_compressed(tmp_path):
    """4 workers, compressed pushes, bit-identical pulls (parity:
    tests/nightly/dist_sync_kvstore.py compressed section)."""
    import subprocess
    import sys

    from mxnet_tpu.kvstore_server import KVServer
    num_workers = 4
    port = 19261
    server = KVServer(port=port, num_workers=num_workers)
    t = threading.Thread(target=server.run, daemon=True)
    t.start()
    time.sleep(0.2)
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(_WORKER)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    outs = [str(tmp_path / f"out{r}.npy") for r in range(num_workers)]
    procs = [subprocess.Popen(
        [sys.executable, script, str(r), str(num_workers), str(port),
         outs[r]], env=env) for r in range(num_workers)]
    for p in procs:
        assert p.wait(timeout=180) == 0
    server._stop.set()
    results = [np.load(o) for o in outs]
    # bit-exact across all 4 workers
    for r in results[1:]:
        np.testing.assert_array_equal(results[0], r)
    # server-side SGD (lr=1): w = -sum over rounds of the aggregated
    # (4-worker) dequantized gradients.  All workers emit identically, so
    # per-worker cumulative emission = -w/4, which error feedback keeps
    # within one threshold of the true cumulative gradient 6*g.
    # lag bound: one push emits at most one +-threshold level, so the
    # residual can hold up to threshold + per-push-grad
    per_worker = -results[0] / num_workers
    np.testing.assert_allclose(per_worker[:4], 6 * 0.3, atol=0.25 + 0.3)
    np.testing.assert_allclose(per_worker[4:], 6 * 0.1, atol=0.25 + 0.1)
    # and something was actually emitted (the wire path works)
    assert (per_worker[:4] > 0).all()


# -- traced collective codecs (ISSUE 11) -------------------------------------
def test_jnp_quantize_matches_numpy_reference():
    """The in-trace kTwoBit codec (quantize_2bit_flat/decode_2bit_sum)
    must emit exactly the NumPy reference's codes and keep the same
    error-feedback residual."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.gradient_compression import (decode_2bit_sum,
                                                quantize_2bit_flat)

    rng = np.random.RandomState(7)
    grad = rng.randn(37).astype(np.float32)  # non-multiple-of-4 length
    res = rng.randn(37).astype(np.float32) * 0.1

    ref = GradientCompression(threshold=0.5)
    ref._residuals["k"] = res.copy()
    ref_packed = ref.quantize("k", grad)
    ref_deq = ref.dequantize(ref_packed, grad.shape)

    packed, new_res = jax.jit(
        lambda f, r: quantize_2bit_flat(f, r, 0.5))(grad, res)
    np.testing.assert_array_equal(np.asarray(packed), ref_packed)
    np.testing.assert_allclose(np.asarray(new_res),
                               ref._residuals["k"], atol=1e-6)
    # decode-sum over a fake 2-rank gather == sum of dequantized values
    gathered = jnp.stack([jnp.asarray(packed), jnp.asarray(packed)])
    summed = jax.jit(
        lambda g: decode_2bit_sum(g, 0.5, grad.shape[0]))(gathered)
    np.testing.assert_allclose(np.asarray(summed), 2 * ref_deq,
                               atol=1e-6)


def test_codec_wire_bytes_ring_math():
    from mxnet_tpu.gradient_compression import codec_wire_bytes

    B = 1 << 20
    # dense ring all-reduce: 2 * B * (R-1)/R
    assert codec_wire_bytes(B, 8, "none") == int(2 * B * 7 / 8)
    # fp16 halves it
    assert codec_wire_bytes(B, 8, "fp16") == int(B * 7 / 8)
    # 2bit: (R-1) * B/16 -> dense/2bit == 32/R
    assert codec_wire_bytes(B, 8, "2bit") == int(7 * B / 16)
    ratio = codec_wire_bytes(B, 8, "none") / codec_wire_bytes(B, 8,
                                                              "2bit")
    assert abs(ratio - 32 / 8) < 1e-9
    # R=2 (the cross-host pair): 16x
    r2 = codec_wire_bytes(B, 2, "none") / codec_wire_bytes(B, 2, "2bit")
    assert abs(r2 - 16.0) < 1e-9
