"""Runtime kernel compilation — mx.rtc PallasModule (parity:
python/mxnet/rtc.py CudaModule + tests/python/gpu/test_rtc.py; the
kernel language here is Pallas, run in interpret mode off-TPU)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError

AXPY_SRC = """
def axpy(x_ref, y_ref, alpha):
    y_ref[...] += alpha * x_ref[...]
"""


def test_axpy_matches_reference_example():
    # the reference's doc example (rtc.py:42) translated to Pallas
    mod = mx.rtc.PallasModule(AXPY_SRC)
    k = mod.get_kernel("axpy", "const float *x, float *y, float alpha")
    x = nd.ones((10,))
    y = nd.zeros((10,))
    k.launch([x, y, 3.0], mx.cpu(0), (1, 1, 1), (10, 1, 1))
    np.testing.assert_allclose(y.asnumpy(), 3.0)
    # launch again: in-place += accumulates, like the CUDA kernel would
    k.launch([x, y, 3.0], mx.cpu(0), (1, 1, 1), (10, 1, 1))
    np.testing.assert_allclose(y.asnumpy(), 6.0)


def test_cudamodule_alias_and_multi_output():
    src = """
def scale2(x_ref, a_ref, b_ref):
    a_ref[...] = x_ref[...] * 2.0
    b_ref[...] = x_ref[...] * 3.0
"""
    mod = mx.rtc.CudaModule(src)  # source-compat alias
    k = mod.get_kernel("scale2", "const float *x, float *a, float *b")
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    a = nd.zeros((2, 3))
    b = nd.zeros((2, 3))
    k.launch([x, a, b], mx.cpu(0), (1, 1, 1))
    np.testing.assert_allclose(a.asnumpy(), x.asnumpy() * 2)
    np.testing.assert_allclose(b.asnumpy(), x.asnumpy() * 3)


def test_grid_partitioning():
    # a real multi-program grid: each program indexes its own row by
    # pl.program_id (full arrays are visible; the kernel partitions)
    src = """
def rowscale(x_ref, y_ref, alpha):
    i = pl.program_id(0)
    y_ref[i, :] = x_ref[i, :] * alpha
"""
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("rowscale", "const float *x, float *y, float alpha")
    x = nd.array(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    y = nd.zeros((8, 4))
    k.launch([x, y, 0.5], mx.cpu(0), (8, 1, 1))
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 0.5, rtol=1e-6)


def test_grid_guard_rejects_non_grid_aware_kernels():
    # an accumulating whole-array kernel on a >1 grid would silently run
    # prod(grid) times — the launch must refuse instead
    mod = mx.rtc.PallasModule(AXPY_SRC)
    k = mod.get_kernel("axpy", "const float *x, float *y, float alpha")
    x, y = nd.ones((8,)), nd.zeros((8,))
    with pytest.raises(MXNetError, match="program_id"):
        k.launch([x, y, 1.0], mx.cpu(0), (4, 1, 1))


def test_grid_guard_is_per_kernel_in_mixed_modules():
    # a sibling kernel's program_id use must not vouch for axpy
    src = AXPY_SRC + """

def rowscale(x_ref, y_ref, alpha):
    i = pl.program_id(0)
    y_ref[i, :] = x_ref[i, :] * alpha
"""
    mod = mx.rtc.PallasModule(src)
    bad = mod.get_kernel("axpy", "const float *x, float *y, float alpha")
    x, y = nd.ones((8,)), nd.zeros((8,))
    with pytest.raises(MXNetError, match="program_id"):
        bad.launch([x, y, 1.0], mx.cpu(0), (4, 1, 1))
    ok = mod.get_kernel("rowscale",
                        "const float *x, float *y, float alpha")
    x2, y2 = nd.ones((8, 4)), nd.zeros((8, 4))
    ok.launch([x2, y2, 2.0], mx.cpu(0), (8, 1, 1))
    np.testing.assert_allclose(y2.asnumpy(), 2.0)


def test_launch_validates_arg_count_and_dtype():
    mod = mx.rtc.PallasModule(AXPY_SRC)
    k = mod.get_kernel("axpy", "const float *x, float *y, float alpha")
    x, y = nd.ones((4,)), nd.zeros((4,))
    with pytest.raises(MXNetError, match="declares 3 args"):
        k.launch([x, y], mx.cpu(0), (1,))
    xi = nd.array(np.ones(4, np.int32))
    with pytest.raises(MXNetError, match="declared float32"):
        k.launch([xi, y, 1.0], mx.cpu(0), (1,))


def test_signature_and_name_errors():
    mod = mx.rtc.PallasModule(AXPY_SRC)
    with pytest.raises(MXNetError):
        mod.get_kernel("nope", "const float *x")
    with pytest.raises(MXNetError):
        mod.get_kernel("axpy", "const quux *x")
    with pytest.raises(MXNetError):
        mx.rtc.PallasModule("def broken(:\n  pass")
    k = mod.get_kernel("axpy", "const float *x, float *y, float alpha")
    with pytest.raises(MXNetError):
        k.launch([1.0, nd.zeros((4,)), 2.0], mx.cpu(0), (1,))


def test_int_dtype_kernel():
    src = """
def addi(x_ref, y_ref, k):
    y_ref[...] = x_ref[...] + k
"""
    mod = mx.rtc.PallasModule(src)
    kern = mod.get_kernel("addi", "const int32 *x, int32 *y, int32 k")
    x = nd.array(np.arange(5, dtype=np.int32))
    y = nd.array(np.zeros(5, dtype=np.int32))
    kern.launch([x, y, 7], mx.cpu(0), (1,))
    np.testing.assert_array_equal(y.asnumpy(), np.arange(5) + 7)
