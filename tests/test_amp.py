"""AMP tests (parity intent: reference tests/python/gpu/test_contrib_amp.py
— init() routes precision by op lists, loss scaler skips bad steps,
training under amp matches fp32 closely)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, gluon, nd
from mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _amp_off():
    yield
    amp.deinit()


def test_amp_routes_matmul_to_bf16():
    amp.init(target_dtype="bfloat16")
    x = nd.array(np.random.randn(4, 8).astype(np.float32))
    w = nd.array(np.random.randn(8, 8).astype(np.float32))
    out = nd.dot(x, w)
    assert str(out.dtype) == "bfloat16"
    # deny-listed op gets fp32 back
    s = nd.softmax(out)
    assert str(s.dtype) == "float32"


def test_amp_off_is_fp32():
    x = nd.array(np.random.randn(4, 8).astype(np.float32))
    w = nd.array(np.random.randn(8, 8).astype(np.float32))
    assert str(nd.dot(x, w).dtype) == "float32"


def test_amp_mlp_converges_close_to_fp32():
    """bf16 AMP training tracks fp32 training (the MFU recipe is safe)."""
    np.random.seed(0)
    x_np = np.random.randn(64, 16).astype(np.float32)
    y_np = (np.arange(64) % 10).astype(np.float32)

    def run(use_amp):
        if use_amp:
            amp.init(target_dtype="bfloat16")
        else:
            amp.deinit()
        mx.random.seed(3)
        np.random.seed(3)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
        net.initialize(mx.initializer.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.2})
        lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
        x, y = nd.array(x_np), nd.array(y_np)
        losses = []
        for _ in range(60):
            with mx.autograd.record():
                l = lossfn(net(x), y).mean()
            l.backward()
            tr.step(1)
            losses.append(float(l.asscalar()))
        amp.deinit()
        return losses

    fp32 = run(False)
    bf16 = run(True)
    assert bf16[-1] < bf16[0] * 0.5, bf16
    # same ballpark as fp32 (bf16 rounding means not bit-identical)
    assert abs(bf16[-1] - fp32[-1]) < 0.3, (fp32[-1], bf16[-1])


def test_loss_scaler_skips_overflow_and_halves_scale():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    x = nd.array(np.random.randn(2, 3).astype(np.float32))
    y = nd.array(np.random.randn(2, 4).astype(np.float32))
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    amp.init_trainer(tr)
    scaler = tr._amp_loss_scaler
    lossfn = gluon.loss.L2Loss()
    with mx.autograd.record():
        l = lossfn(net(x), y).mean()
        with amp.scale_loss(l, tr) as scaled:
            scaled.backward()
    before = [p.data().asnumpy().copy()
              for p in net.collect_params().values()]
    # poison one gradient with inf -> step must skip and halve the scale
    p0 = list(net.collect_params().values())[0]
    g = p0.list_grad()[0]
    g[:] = nd.array(np.full(g.shape, np.inf, np.float32))
    s0 = scaler.loss_scale
    tr.step(1)
    after = [p.data().asnumpy() for p in net.collect_params().values()]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b, a)
    assert scaler.loss_scale == s0 / 2


def test_loss_scaler_backoff_growth_sequence_unchanged():
    """ISSUE-14 satellite parity: has_overflow now routes through the
    numerics observatory's fused multi-all-finite sentinel — the
    dynamic-scale backoff/growth SEQUENCE must be unchanged vs the
    definition (halve on overflow, double after scale_window clean
    steps), and the verdicts must match a per-array numpy check."""
    from mxnet_tpu.amp import LossScaler

    rng = np.random.RandomState(0)
    clean = [nd.array(rng.randn(4, 3).astype(np.float32))
             for _ in range(3)]
    poisoned = [g.copy() for g in clean]
    poisoned[1] = nd.array(
        np.where(np.arange(12).reshape(4, 3) == 7, np.inf,
                 rng.randn(4, 3)).astype(np.float32))
    nan_poisoned = [g.copy() for g in clean]
    nan_poisoned[0] = nd.array(np.full((4, 3), np.nan, np.float32))

    scaler = LossScaler(init_scale=2. ** 8, scale_factor=2.,
                        scale_window=3)
    # verdicts match the per-array reference check
    assert scaler.has_overflow(poisoned) is True
    assert scaler.has_overflow(nan_poisoned) is True
    assert scaler.has_overflow(clean) is False
    assert scaler.has_overflow([None, clean[0]]) is False

    # sequence parity: drive the scaler through a scripted overflow
    # pattern twice — once via has_overflow + update_scale, once via
    # update_from_window (the in-window flag feed) — same scale at
    # every point
    pattern = [False, True, False, False, False, True, False, False,
               False, False]
    a = LossScaler(init_scale=2. ** 8, scale_factor=2., scale_window=3)
    scales_a = []
    for ov in pattern:
        grads = poisoned if ov else clean
        a.update_scale(a.has_overflow(grads))
        scales_a.append(a.loss_scale)
    b = LossScaler(init_scale=2. ** 8, scale_factor=2., scale_window=3)
    b.update_from_window(pattern)
    assert scales_a[-1] == b.loss_scale
    # the canonical sequence: halve at each overflow, double after 3
    # consecutive clean steps
    c = LossScaler(init_scale=2. ** 8, scale_factor=2., scale_window=3)
    scales_c = []
    for ov in pattern:
        c.update_scale(ov)
        scales_c.append(c.loss_scale)
    assert scales_a == scales_c


def test_convert_hybrid_block():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8), nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    net(nd.array(np.random.randn(2, 6).astype(np.float32)))
    amp.convert_hybrid_block(net, "bfloat16")
    dts = {p.name: str(p.data().dtype)
           for p in net.collect_params().values()}
    assert all(d == "bfloat16" for n, d in dts.items() if "weight" in n)
    assert all(d == "float32" for n, d in dts.items() if "bias" in n)


def test_amp_lists_name_real_ops():
    """Every name in amp/lists.py is a registered op (r03 verdict: the
    lists once named SVMOutput before it existed; this pins them to the
    live registry so entries cannot rot)."""
    from mxnet_tpu.ops import registry
    from mxnet_tpu.amp import lists
    all_names = set()
    for attr in dir(lists):
        val = getattr(lists, attr)
        if isinstance(val, (list, tuple, set, frozenset)) and \
                not attr.startswith("_"):
            all_names |= set(val)
    assert all_names, "amp lists unexpectedly empty"
    missing = sorted(n for n in all_names if not registry.exists(n))
    assert not missing, f"amp lists name unregistered ops: {missing}"
