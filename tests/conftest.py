"""Test harness configuration.

Forces an 8-device virtual CPU mesh (parity with the reference's strategy of
running the whole unit suite per backend, SURVEY.md §4): sharding/collective
tests exercise real multi-device code paths without TPU hardware.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seeded():
    """Reproducible-but-varied RNG per test (parity: with_seed() decorator in
    reference tests/python/unittest/common.py)."""
    import mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield
