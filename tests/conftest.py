"""Test harness configuration.

Forces an 8-device virtual CPU mesh (parity with the reference's strategy of
running the whole unit suite per backend, SURVEY.md §4): sharding/collective
tests exercise real multi-device code paths without TPU hardware.

The axon TPU plugin (registered at interpreter startup via sitecustomize)
is unregistered here: unit tests are CPU-only by design, and initializing
the axon client adds a network roundtrip per backend init (and hangs the
suite outright if the TPU tunnel is down).
"""
import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
# hermetic persistent-compilation-cache location: a test that triggers
# mxnet_tpu.compile.ensure_persistent_cache must never write artifacts
# into the developer's $XDG_CACHE_HOME
os.environ.setdefault(
    "MXNET_COMPILE_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "mxnet-tpu-test-compile-cache"))
# hermetic flight-recorder dump location: watchdog fires / chaos kills
# inside tests must not litter the developer's cwd with
# mxnet-flight-*.json rings (tests that assert on dumps pin their own
# MXNET_FLIGHT_DIR via monkeypatch)
_flight_dir = os.path.join(tempfile.gettempdir(), "mxnet-tpu-test-flight")
os.makedirs(_flight_dir, exist_ok=True)
os.environ.setdefault("MXNET_FLIGHT_DIR", _flight_dir)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

try:  # drop the axon TPU plugin before any backend initializes
    import jax
    import jax._src.xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    # sitecustomize imported jax before this file ran, so the env var was
    # captured already — update the live config too
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seeded():
    """Reproducible-but-varied RNG per test (parity: with_seed() decorator
    in reference tests/python/unittest/common.py). MXNET_TEST_SEED varies
    the base seed — tools/flakiness_checker.py sets it per trial."""
    import mxnet_tpu as mx
    seed = int(os.environ.get("MXNET_TEST_SEED", 0))
    np.random.seed(seed)
    mx.random.seed(seed)
    yield
