"""ISSUE 16 — stateful generation: sessions, paged KV, prefix reuse.

The acceptance pins:

* a session's tokens are BITWISE identical batched vs unbatched
  (greedy and seeded sampling), with >= 2 sessions genuinely sharing
  a decode micro-batch;
* ZERO decode-step compiles after warm (trace-time counter, not a
  timing observation), and decode dispatches are SHARED across active
  sessions (< 1 dispatch per token once batched);
* KV slot-pool admission charges the resource ledger and provably
  releases on every exit path (done / typed failure / close);
* the prefix cache hits page-aligned shared heads, changes nothing
  bitwise, and a version flip invalidates its stale activations;
* per-session "generation" traces tile the session wall with named
  stages;
* a non-finite decode row fails THAT session typed while cohort
  siblings keep streaming;
* the chaos scenario: an engine killed mid-stream fails sessions
  typed-retryable, siblings resume them, nothing leaks.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (pins the CPU backend via conftest)
from mxnet_tpu.base import MXNetError, NonFiniteError
from mxnet_tpu.serving import (CohortQueue, GenerationEngine,
                               KVPoolExhaustedError, KVSlotPool,
                               PrefixCache, ServingOverloadError,
                               ServingWorkerError, tiny_lm)
from mxnet_tpu.serving.kv_cache import pages_for
from mxnet_tpu.telemetry import trace as mxtrace
from mxnet_tpu.telemetry.resources import LEDGER

VOCAB, DM, MAXLEN = 24, 8, 64


def _engine(name, seed=2, prefix=0, slots=4, jit=True, **kw):
    model = tiny_lm(vocab=VOCAB, d_model=DM, max_len=MAXLEN, seed=seed,
                    jit=jit, **{k: kw.pop(k) for k in ("eos_id",
                                                       "per_token_cost_s")
                                if k in kw})
    return GenerationEngine(model, name=name, slots=slots, page_tokens=8,
                            kv_budget_mb=8, prefix_cache_entries=prefix,
                            max_len=MAXLEN, **kw)


def _prompts():
    return [np.arange(1, 1 + n, dtype=np.int32) % (VOCAB - 1) + 1
            for n in (5, 9, 13, 3)]


def _run_unbatched(name, greedy, seed=2):
    eng = _engine(name, seed=seed)
    eng.warm()
    try:
        return [eng.generate(p, max_new_tokens=8, greedy=greedy,
                             seed=7 + i)
                for i, p in enumerate(_prompts())]
    finally:
        eng.close()


def _run_batched(name, greedy, seed=2):
    eng = _engine(name, seed=seed)
    eng.warm()
    try:
        sessions = [eng.start_session(p, max_new_tokens=8, greedy=greedy,
                                      seed=7 + i)
                    for i, p in enumerate(_prompts())]
        out = [s.result(timeout=60) for s in sessions]
        return out, eng.stats()
    finally:
        eng.close()


# -- bitwise identity ---------------------------------------------------------
def test_batched_greedy_bitwise_identical_to_unbatched():
    want = _run_unbatched("gen-u-g", greedy=True)
    got, stats = _run_batched("gen-b-g", greedy=True)
    assert got == want
    # the identity must have been exercised BATCHED: sessions genuinely
    # shared decode micro-batches, on shared dispatches
    assert stats["max_active"] >= 2
    assert stats["decode_steps"] < 4 * 8


def test_batched_seeded_sampling_bitwise_identical_to_unbatched():
    """Seeded host-side sampling is sensitive to every logits ulp, so
    this pins bitwise row-independence of the packed decode step (the
    padding-row scatter-drop included), not just argmax stability."""
    want = _run_unbatched("gen-u-s", greedy=False)
    got, stats = _run_batched("gen-b-s", greedy=False)
    assert got == want
    assert stats["max_active"] >= 2


# -- compile discipline -------------------------------------------------------
def test_zero_decode_compiles_after_warm():
    eng = _engine("gen-compiles")
    warmed = eng.warm()
    try:
        assert warmed  # the prefill prompt ladder compiled
        s0 = eng.stats()
        assert s0["decode_compiles"] == 1   # exactly the warm trace
        sessions = [eng.start_session(p, max_new_tokens=8)
                    for p in _prompts()]
        for s in sessions:
            s.result(timeout=60)
        s1 = eng.stats()
        assert s1["decode_compiles"] == s0["decode_compiles"]
        assert s1["prefill_compiles"] == s0["prefill_compiles"]
        assert s1["tokens_emitted"] == 4 * 8
    finally:
        eng.close()


# -- slot pool + ledger -------------------------------------------------------
def test_slot_pool_ledger_roundtrip_and_idempotent_release():
    pool = KVSlotPool("generation/t-pool", slots=2, page_tokens=8,
                      bytes_per_token=64, budget_bytes=1 << 20)
    a = pool.acquire("s1", 16)
    assert a.pages == pages_for(16, 8) == 2
    owners = LEDGER.snapshot()["owners"]
    assert owners["generation/t-pool"]["kv_pages"] == a.nbytes
    b = pool.acquire("s2", 6)
    with pytest.raises(KVPoolExhaustedError):
        pool.acquire("s3", 6)
    pool.release(a)
    pool.release(a)   # idempotent: double release must not go negative
    pool.release(b)
    st = pool.stats()
    assert st["slots_in_use"] == 0 and st["kv_bytes"] == 0
    assert st["acquires"] == 2 and st["releases"] == 2 and st["sheds"] == 1
    assert LEDGER.snapshot()["owners"]["generation/t-pool"]["kv_pages"] == 0


def test_kv_budget_blow_sheds_typed():
    pool = KVSlotPool("generation/t-budget", slots=8, page_tokens=8,
                      bytes_per_token=64, budget_bytes=2 * 8 * 64)
    pool.acquire("s1", 16)                             # exactly the budget
    with pytest.raises(KVPoolExhaustedError) as e:
        pool.acquire("s2", 2)
    assert isinstance(e.value, ServingOverloadError)
    assert isinstance(e.value, MXNetError)


def test_engine_pool_full_sheds_typed_and_admission_validates():
    eng = _engine("gen-full", slots=1, jit=False, per_token_cost_s=0.01)
    try:
        hog = eng.start_session(np.arange(1, 5, dtype=np.int32),
                                max_new_tokens=16)
        with pytest.raises(ServingOverloadError):
            eng.start_session(np.array([1, 2], np.int32), max_new_tokens=4)
        with pytest.raises(MXNetError):
            eng.start_session(np.array([], np.int32))      # empty prompt
        with pytest.raises(MXNetError):
            eng.start_session(np.array([1], np.int32),
                              max_new_tokens=MAXLEN + 1)   # arena overflow
        assert len(hog.result(timeout=60)) == 16
    finally:
        eng.close()
    st = eng.stats()
    assert st["kv"]["slots_in_use"] == 0 and st["kv"]["kv_bytes"] == 0


def test_sessions_release_ledger_to_zero():
    eng = _engine("gen-ledger")
    eng.warm()
    try:
        sessions = [eng.start_session(p, max_new_tokens=6)
                    for p in _prompts()]
        for s in sessions:
            s.result(timeout=60)
    finally:
        eng.close()
    owner = f"generation/{eng.name}"
    assert LEDGER.snapshot()["owners"][owner]["kv_pages"] == 0
    st = eng.stats()["kv"]
    assert st["acquires"] == 4 and st["releases"] == 4


# -- prefix cache -------------------------------------------------------------
def test_prefix_cache_page_alignment_hit_and_miss():
    cache = PrefixCache("generation/t-px", capacity=4, page_tokens=8)
    prompt = np.arange(1, 20, dtype=np.int32)         # len 19
    kv = {"k": np.ones((19, 4), np.float32)}
    stored = cache.store("m", 1, prompt, kv)
    assert stored == 16                                # page-aligned clip
    hit_len, got = cache.lookup("m", 1, prompt)
    assert hit_len == 16 and got["k"].shape[0] == 16
    # a hit may never cover the WHOLE prompt: the last token must
    # recompute so the session has first-sample logits
    short = np.arange(1, 9, dtype=np.int32)            # len 8
    cache.store("m", 1, short, {"k": np.ones((8, 4), np.float32)})
    hl, _ = cache.lookup("m", 1, short)
    assert hl == 0                                     # 8 == len, capped out
    assert cache.lookup("m", 1, np.arange(50, 60, dtype=np.int32))[0] == 0
    st = cache.stats()
    assert st["hits"] == 1 and st["misses"] >= 1


def test_prefix_cache_version_flip_invalidates():
    cache = PrefixCache("generation/t-flip", capacity=4, page_tokens=8)
    prompt = np.arange(1, 20, dtype=np.int32)
    cache.store("m", 1, prompt, {"k": np.ones((19, 4), np.float32)})
    cache.store("m", 2, prompt, {"k": np.ones((19, 4), np.float32)})
    cache.store("other", 1, prompt, {"k": np.ones((19, 4), np.float32)})
    cache.evict_stale_versions("m", keep_versions={2})
    assert cache.lookup("m", 1, prompt)[0] == 0        # v1 gone
    assert cache.lookup("m", 2, prompt)[0] == 16       # v2 kept
    assert cache.lookup("other", 1, prompt)[0] == 16   # other model kept
    assert LEDGER.snapshot()["owners"]["generation/t-flip"][
        "prefix_cache"] > 0
    cache.clear()
    assert LEDGER.snapshot()["owners"]["generation/t-flip"][
        "prefix_cache"] == 0


def test_prefix_hit_is_bitwise_invisible():
    shared = np.arange(1, 20, dtype=np.int32) % (VOCAB - 1) + 1
    p1 = np.concatenate([shared, np.array([3, 4], np.int32)])
    p2 = np.concatenate([shared, np.array([5, 6, 7], np.int32)])

    eng = _engine("gen-px", seed=3, prefix=8)
    eng.warm()
    try:
        a1 = eng.generate(p1, max_new_tokens=6)
        a2 = eng.generate(p2, max_new_tokens=6)        # hits p1's head
        st = eng.stats()["prefix_cache"]
        assert st["hits"] >= 1
    finally:
        eng.close()

    ref = _engine("gen-px-ref", seed=3, prefix=0)
    ref.warm()
    try:
        assert a1 == ref.generate(p1, max_new_tokens=6)
        assert a2 == ref.generate(p2, max_new_tokens=6)
    finally:
        ref.close()


# -- cohort queue -------------------------------------------------------------
def test_cohort_queue_anchors_oldest_and_joins_same_signature():
    q = CohortQueue(lambda x: x[0], max_cohort=3)
    for item in [(8, "a"), (16, "b"), (8, "c"), (8, "d"), (8, "e")]:
        q.put(item)
    cohort = q.take(timeout=0.0)
    # anchor (8,"a") joins the later 8s, skipping the 16 — up to max
    assert cohort == [(8, "a"), (8, "c"), (8, "d")]
    assert q.take(timeout=0.0) == [(16, "b")]
    assert q.take(timeout=0.0) == [(8, "e")]
    assert q.take(timeout=0.0) == []
    q.put((4, "f"))
    assert q.drain() == [(4, "f")] and len(q) == 0


# -- observability ------------------------------------------------------------
def test_generation_trace_stages_tile_the_session():
    mxtrace.enable()
    mxtrace.reset_exemplars()
    eng = _engine("gen-trace")
    eng.warm()
    try:
        eng.generate(np.arange(1, 8, dtype=np.int32), max_new_tokens=6)
        doc = mxtrace.exemplars()["generation"]["last"]
    finally:
        eng.close()
        mxtrace.disable()
        mxtrace.reset_exemplars()
    stages = {s["stage"] for s in doc["stages"]}
    assert {"admit", "prefill_wait", "prefill", "decode_wait",
            "decode_step", "sample", "deliver"} <= stages
    assert doc["coverage"] >= 0.8, doc


def test_shed_admission_finishes_trace_typed():
    """Regression (graftlint resource-leak-on-raise): a pool-full shed
    inside start_session used to leave the freshly-minted "generation"
    span unfinished — every rejected admission leaked a phantom
    in-flight session into the tracer's active set."""
    mxtrace.enable()
    mxtrace.reset_exemplars()
    eng = _engine("gen-shed-trace", slots=1, jit=False,
                  per_token_cost_s=0.01)
    try:
        hog = eng.start_session(np.arange(1, 5, dtype=np.int32),
                                max_new_tokens=16)
        with pytest.raises(ServingOverloadError):
            eng.start_session(np.array([1, 2], np.int32),
                              max_new_tokens=4)
        # the rejected admission's trace FINISHED, typed (the hog's
        # session is still decoding, so its trace cannot be here yet)
        docs = mxtrace.exemplars().get("generation", {})
        finished = list(docs.get("head", []))
        if docs.get("last") is not None:
            finished.append(docs["last"])
        rejected = [d for d in finished if d["status"] == "rejected"]
        assert rejected, f"shed admission left its span open: {docs}"
        assert any(e["event"] == "rejected"
                   for e in rejected[0]["events"])
        assert len(hog.result(timeout=60)) == 16
    finally:
        eng.close()
        mxtrace.disable()
        mxtrace.reset_exemplars()


def test_generation_metric_families_export():
    from mxnet_tpu.telemetry import REGISTRY
    eng = _engine("gen-metrics")
    eng.warm()
    try:
        eng.generate(np.arange(1, 6, dtype=np.int32), max_new_tokens=4)
        snap = REGISTRY.snapshot()
        assert "gen-metrics" in snap["generation"]
        dump = REGISTRY.prometheus_dump()
        for fam in ("mxnet_generation_sessions_total",
                    "mxnet_generation_tokens_total",
                    "mxnet_generation_decode_steps_total",
                    "mxnet_generation_decode_compiles",
                    "mxnet_generation_kv_pages"):
            assert fam in dump, fam
    finally:
        eng.close()


def test_intertoken_reservoir_observed():
    eng = _engine("gen-inter")
    eng.warm()
    try:
        eng.generate(np.arange(1, 6, dtype=np.int32), max_new_tokens=6)
        gaps = eng.metrics.drain_observations("intertoken_ms")
        assert len(gaps) >= 5
        assert all(g >= 0.0 for g in gaps)
    finally:
        eng.close()


# -- output health ------------------------------------------------------------
def test_nonfinite_decode_row_fails_typed_siblings_stream_on():
    base = tiny_lm(vocab=VOCAB, d_model=DM, max_len=MAXLEN, seed=4,
                   jit=False)
    TRIG = VOCAB - 1
    inner = base.decode_fn

    def decode_nan(params, arena, tokens, pos):
        logits, arena = inner(params, arena, tokens, pos)
        logits = np.array(logits)
        logits[np.asarray(tokens) == TRIG] = np.nan
        return logits, arena

    base.decode_fn = decode_nan
    eng = GenerationEngine(base, name="gen-nan", slots=4, page_tokens=8,
                           kv_budget_mb=8, prefix_cache_entries=0,
                           max_len=MAXLEN)
    try:
        # victim's first decode feeds its last prompt token == TRIG
        victim = eng.start_session(np.array([1, 2, TRIG], np.int32),
                                   max_new_tokens=8)
        sibling = eng.start_session(np.array([1, 2, 3], np.int32),
                                    max_new_tokens=8)
        with pytest.raises(NonFiniteError):
            victim.result(timeout=60)
        assert len(sibling.result(timeout=60)) == 8
        st = eng.stats()
        assert st["sessions_failed"] == 1
    finally:
        eng.close()
    assert eng.stats()["kv"]["slots_in_use"] == 0   # victim's slot freed


# -- hot reload / retire ------------------------------------------------------
def test_executor_cache_retire_hook_fires_on_stale_eviction():
    from mxnet_tpu.serving.executor_cache import ExecutorCache
    cache = ExecutorCache(capacity=4)
    seen = []
    cache.add_retire_hook(lambda model, keep: seen.append((model,
                                                           set(keep))))
    cache.evict_stale_versions("m", keep_versions={2})
    assert seen == [("m", {2})]


def test_engine_hot_reload_zero_post_flip_decode_compiles():
    eng = _engine("gen-flip", seed=2)
    eng.warm()
    try:
        v1_out = eng.generate(np.arange(1, 8, dtype=np.int32),
                              max_new_tokens=4)
        v2 = eng.load(tiny_lm(vocab=VOCAB, d_model=DM, max_len=MAXLEN,
                              seed=9))
        compiles_at_flip = eng.stats()["decode_compiles"]
        v2_out = eng.generate(np.arange(1, 8, dtype=np.int32),
                              max_new_tokens=4)
        st = eng.stats()
        assert st["version"] == v2
        assert st["decode_compiles"] == compiles_at_flip  # warmed pre-flip
        assert v2_out != v1_out     # genuinely the new params
        # retire keeps {prev, new}: one flip of headroom, nothing older
        assert v2 in st["versions_resident"]
        assert len(st["versions_resident"]) <= 2
    finally:
        eng.close()


def test_server_load_generator_end_to_end():
    from mxnet_tpu import serving
    server = serving.ModelServer(num_replicas=1, name="gen-srv")
    try:
        v1 = server.load_generator(
            "lm", tiny_lm(vocab=VOCAB, d_model=DM, max_len=MAXLEN, seed=2),
            warm=True, slots=2, page_tokens=8, kv_budget_mb=8,
            prefix_cache_entries=4, max_len=MAXLEN)
        toks = server.generate("lm", np.arange(1, 6, dtype=np.int32),
                               timeout=60, max_new_tokens=4)
        assert len(toks) == 4
        assert "lm" in server.repository.models()
        v2 = server.load_generator(
            "lm", tiny_lm(vocab=VOCAB, d_model=DM, max_len=MAXLEN, seed=9))
        assert v2 > v1
        assert server.generator("lm").stats()["version"] == v2
        snap = server.stats()
        assert snap["generators"]["lm"]["sessions_started"] == 1
    finally:
        server.shutdown()


# -- failure fan-out ----------------------------------------------------------
def test_loop_crash_fails_active_sessions_typed_retryable():
    import mxnet_tpu.chaos as chaos
    chaos.reset()
    eng = _engine("gen-crash", jit=False, per_token_cost_s=0.005,
                  loop_restarts=0)
    try:
        chaos.arm("serving/generation/decode", "raise", hits=2, count=1)
        sess = eng.start_session(np.arange(1, 5, dtype=np.int32),
                                 max_new_tokens=16)
        with pytest.raises(ServingWorkerError) as e:
            sess.result(timeout=60)
        assert e.value.retryable
        with pytest.raises(MXNetError):
            eng.start_session(np.array([1], np.int32))  # failed fast
    finally:
        chaos.reset()
        eng.close()
    st = eng.stats()
    assert st["kv"]["slots_in_use"] == 0                # nothing leaked


@pytest.mark.slow
def test_chaos_scenario_replica_kill_mid_generation():
    from mxnet_tpu import chaos
    from mxnet_tpu.chaos import harness
    chaos.reset()
    try:
        r = harness.scenario_replica_kill_mid_generation(n_sessions=4,
                                                         max_new=6)
    finally:
        chaos.reset()
    assert r["ok"], r
    assert r["hung"] == 0 and not r["non_typed_failures"]
    assert r["zero_leak"], r["leaks"]
