"""Detection/contrib op tests (reference: tests/python/unittest/
test_operator.py box_nms/multibox/ROI cases — forward vs a NumPy oracle,
backward through the gather/scatter paths)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _np_iou(a, b):
    tlx = max(a[0], b[0]); tly = max(a[1], b[1])
    brx = min(a[2], b[2]); bry = min(a[3], b[3])
    i = max(0.0, brx - tlx) * max(0.0, bry - tly)
    u = ((a[2] - a[0]) * (a[3] - a[1])
         + (b[2] - b[0]) * (b[3] - b[1]) - i)
    return 0.0 if u <= 0 else i / u


def test_box_iou_vs_numpy():
    rng = np.random.RandomState(0)
    pts = rng.uniform(0, 1, (5, 2, 2))
    lhs = np.concatenate([pts.min(1), pts.max(1)], axis=1).astype(np.float32)
    pts = rng.uniform(0, 1, (3, 2, 2))
    rhs = np.concatenate([pts.min(1), pts.max(1)], axis=1).astype(np.float32)
    got = nd.contrib.box_iou(nd.array(lhs), nd.array(rhs)).asnumpy()
    want = np.array([[_np_iou(l, r) for r in rhs] for l in lhs])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def _np_box_nms(data, overlap_thresh=0.5, valid_thresh=0, topk=-1,
                coord_start=2, score_index=1, id_index=-1,
                background_id=-1, force_suppress=False):
    """NumPy oracle for one batch (N, W): compact kept rows, -1 fill."""
    n, w = data.shape
    scores = data[:, score_index]
    valid = scores > valid_thresh
    if id_index >= 0:
        valid &= data[:, id_index] != background_id
    order = sorted(range(n), key=lambda i: (-scores[i], i))
    order = [i for i in order if valid[i]]
    k = len(order) if topk < 0 else min(topk, len(order))
    order = order[:k]
    kept = []
    for i in order:
        ok = True
        for j in kept:
            if (force_suppress or id_index < 0
                    or data[i, id_index] == data[j, id_index]):
                if _np_iou(data[i, coord_start:coord_start + 4],
                           data[j, coord_start:coord_start + 4]) \
                        > overlap_thresh:
                    ok = False
                    break
        if ok:
            kept.append(i)
    out = np.full((n, w), -1.0, np.float32)
    for slot, i in enumerate(kept):
        out[slot] = data[i]
    return out


@pytest.mark.parametrize("force", [False, True])
def test_box_nms_vs_numpy(force):
    rng = np.random.RandomState(42)
    n = 32
    pts = rng.uniform(0, 1, (n, 2, 2)).astype(np.float32)
    boxes = np.concatenate([pts.min(1), pts.max(1)], axis=1)
    cls = rng.randint(0, 3, (n, 1)).astype(np.float32)
    score = rng.uniform(0, 1, (n, 1)).astype(np.float32)
    data = np.concatenate([cls, score, boxes], axis=1)[None]  # (1,N,6)
    got = nd.contrib.box_nms(
        nd.array(data), overlap_thresh=0.5, valid_thresh=0.1,
        id_index=0, force_suppress=force).asnumpy()
    want = _np_box_nms(data[0], overlap_thresh=0.5, valid_thresh=0.1,
                       id_index=0, force_suppress=force)[None]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_box_nms_topk_and_batch():
    rng = np.random.RandomState(3)
    data = rng.uniform(0, 1, (2, 3, 10, 6)).astype(np.float32)
    got = nd.contrib.box_nms(nd.array(data), overlap_thresh=0.7,
                             valid_thresh=0.3, topk=4).asnumpy()
    for b in range(2):
        for c in range(3):
            want = _np_box_nms(data[b, c], overlap_thresh=0.7,
                               valid_thresh=0.3, topk=4)
            np.testing.assert_allclose(got[b, c], want, rtol=1e-5,
                                       atol=1e-6)


def test_box_nms_backward_scatters_to_kept():
    data = np.array([[[0.9, 0, 0, 1, 1],
                      [0.8, 0, 0, .9, .9],
                      [0.7, 2, 2, 3, 3]]], np.float32)
    x = nd.array(data)
    x.attach_grad()
    with mx.autograd.record():
        out = nd.contrib.box_nms(x, overlap_thresh=0.5, coord_start=1,
                                 score_index=0)
        loss = (out * out).sum()
    loss.backward()
    g = x.grad.asnumpy()
    # kept rows (0 and 2) receive 2*x, the suppressed row receives 0
    np.testing.assert_allclose(g[0, 0], 2 * data[0, 0], rtol=1e-5)
    np.testing.assert_allclose(g[0, 2], 2 * data[0, 2], rtol=1e-5)
    np.testing.assert_allclose(g[0, 1], np.zeros(5), atol=1e-6)


def test_multibox_prior_matches_reference_layout():
    h, w = 3, 4
    sizes, ratios = (0.4, 0.8), (1.0, 2.0, 0.5)
    x = nd.zeros((1, 2, h, w))
    got = nd.contrib.MultiBoxPrior(
        x, sizes=sizes, ratios=ratios).asnumpy()[0]
    # oracle: direct port of the loop in multibox_prior.cc:43-73
    want = []
    for r in range(h):
        cy = (r + 0.5) / h
        for c in range(w):
            cx = (c + 0.5) / w
            rat = np.sqrt(ratios[0])
            for s in sizes:
                bw = s * h / w * rat / 2
                bh = s / rat / 2
                want.append([cx - bw, cy - bh, cx + bw, cy + bh])
            for rr in ratios[1:]:
                rat2 = np.sqrt(rr)
                bw = sizes[0] * h / w * rat2 / 2
                bh = sizes[0] / rat2 / 2
                want.append([cx - bw, cy - bh, cx + bw, cy + bh])
    np.testing.assert_allclose(got, np.array(want), rtol=1e-5, atol=1e-6)
    assert got.shape == (h * w * (len(sizes) + len(ratios) - 1), 4)


def test_multibox_target_basic_matching():
    # 4 hand-placed anchors, 1 gt that clearly overlaps anchor 0
    anchors = np.array([[0.0, 0.0, 0.5, 0.5],
                        [0.5, 0.5, 1.0, 1.0],
                        [0.0, 0.5, 0.5, 1.0],
                        [0.4, 0.0, 0.9, 0.5]], np.float32)[None]
    label = np.array([[[2, 0.05, 0.05, 0.45, 0.45],
                       [-1, -1, -1, -1, -1]]], np.float32)
    cls_pred = np.zeros((1, 4, 4), np.float32)
    lt, lm, ct = nd.contrib.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred))
    ct = ct.asnumpy()[0]
    # anchor 0 positive with class 2 -> target 3 (0 = background)
    assert ct[0] == 3.0
    # everything else negative (no mining)
    np.testing.assert_allclose(ct[1:], 0.0)
    lm = lm.asnumpy()[0].reshape(4, 4)
    np.testing.assert_allclose(lm[0], 1.0)
    np.testing.assert_allclose(lm[1:], 0.0)
    # loc target encodes (gt - anchor) / variance
    ltv = lt.asnumpy()[0].reshape(4, 4)
    aw = ah = 0.5
    gx, gy, gw, gh = 0.25, 0.25, 0.4, 0.4
    want = [(gx - 0.25) / aw / 0.1, (gy - 0.25) / ah / 0.1,
            np.log(gw / aw) / 0.2, np.log(gh / ah) / 0.2]
    np.testing.assert_allclose(ltv[0], want, rtol=1e-4, atol=1e-5)


def test_multibox_target_negative_mining():
    rng = np.random.RandomState(0)
    a = 16
    cy = cx = (np.arange(4) + 0.5) / 4
    grid = np.stack(np.meshgrid(cx, cy), -1).reshape(-1, 2)
    anchors = np.concatenate([grid - 0.12, grid + 0.12],
                             axis=1).astype(np.float32)[None]
    label = np.array([[[0, 0.05, 0.05, 0.3, 0.3]]], np.float32)
    cls_pred = rng.randn(1, 3, a).astype(np.float32)
    lt, lm, ct = nd.contrib.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred),
        negative_mining_ratio=2.0, negative_mining_thresh=0.5)
    ct = ct.asnumpy()[0]
    n_pos = int((ct > 0).sum())
    n_neg = int((ct == 0).sum())
    n_ign = int((ct == -1).sum())
    assert n_pos >= 1
    assert n_neg == min(2 * n_pos, a - n_pos)
    assert n_pos + n_neg + n_ign == a


def test_multibox_target_no_gt():
    anchors = np.array([[[0, 0, .5, .5], [.5, .5, 1, 1]]], np.float32)
    label = -np.ones((1, 2, 5), np.float32)
    cls_pred = np.zeros((1, 3, 2), np.float32)
    lt, lm, ct = nd.contrib.MultiBoxTarget(
        nd.array(anchors), nd.array(label), nd.array(cls_pred))
    np.testing.assert_allclose(ct.asnumpy(), -1.0)
    np.testing.assert_allclose(lt.asnumpy(), 0.0)
    np.testing.assert_allclose(lm.asnumpy(), 0.0)


def test_multibox_detection_decode_and_nms():
    # 3 anchors; anchor 0/1 same spot (class 1 wins both), anchor 2 far
    anchors = np.array([[[0.1, 0.1, 0.4, 0.4],
                         [0.12, 0.12, 0.42, 0.42],
                         [0.6, 0.6, 0.9, 0.9]]], np.float32)
    cls_prob = np.array([[[0.1, 0.2, 0.05],
                          [0.8, 0.7, 0.05],
                          [0.1, 0.1, 0.9]]], np.float32)  # (1, C=3, A=3)
    loc_pred = np.zeros((1, 12), np.float32)
    out = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors),
        nms_threshold=0.5).asnumpy()[0]
    # rows sorted by score desc: .9 (anchor2, class 1), .8 (anchor0,
    # class 0), .7 (anchor1, class 0 — suppressed by anchor0, id -> -1);
    # decode with zero loc_pred reproduces the anchor box exactly
    np.testing.assert_allclose(out[0], [1, 0.9, 0.6, 0.6, 0.9, 0.9],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out[1], [0, 0.8, 0.1, 0.1, 0.4, 0.4],
                               rtol=1e-5, atol=1e-6)
    assert out[2, 0] == -1.0
    assert out[2, 1] == pytest.approx(0.7)


def test_multibox_detection_threshold():
    anchors = np.array([[[0.1, 0.1, 0.4, 0.4]]], np.float32)
    cls_prob = np.array([[[0.99], [0.005], [0.005]]], np.float32)
    loc_pred = np.zeros((1, 4), np.float32)
    out = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), nd.array(anchors),
        threshold=0.01).asnumpy()[0]
    assert out[0, 0] == -1.0  # best fg score below threshold -> invalid


def _np_roi_align(feat, roi, ph, pw, scale, sg):
    c, h, w = feat.shape
    sw, sh, ew, eh = roi[1] * scale, roi[2] * scale, roi[3] * scale, \
        roi[4] * scale
    rw = max(ew - sw, 1.0); rh = max(eh - sh, 1.0)
    bw, bh = rw / pw, rh / ph
    out = np.zeros((c, ph, pw), np.float32)
    for py in range(ph):
        for px in range(pw):
            acc = np.zeros(c, np.float32)
            for iy in range(sg):
                y = sh + py * bh + (iy + 0.5) * bh / sg
                for ix in range(sg):
                    x = sw + px * bw + (ix + 0.5) * bw / sg
                    if y < -1.0 or y > h or x < -1.0 or x > w:
                        continue
                    yy, xx = max(y, 0.0), max(x, 0.0)
                    y0, x0 = int(min(np.floor(yy), h - 1)), \
                        int(min(np.floor(xx), w - 1))
                    y1, x1 = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
                    fy, fx = yy - y0, xx - x0
                    acc += ((1 - fy) * (1 - fx) * feat[:, y0, x0]
                            + (1 - fy) * fx * feat[:, y0, x1]
                            + fy * (1 - fx) * feat[:, y1, x0]
                            + fy * fx * feat[:, y1, x1])
            out[:, py, px] = acc / (sg * sg)
    return out


def test_roi_align_vs_numpy():
    rng = np.random.RandomState(1)
    feat = rng.randn(2, 3, 8, 8).astype(np.float32)
    rois = np.array([[0, 1.0, 1.0, 6.0, 6.0],
                     [1, 0.0, 2.0, 7.5, 7.5],
                     [0, 3.0, 3.0, 4.0, 4.0]], np.float32)
    got = nd.contrib.ROIAlign(nd.array(feat), nd.array(rois),
                              pooled_size=(3, 3), spatial_scale=0.5,
                              sample_ratio=2).asnumpy()
    for i, roi in enumerate(rois):
        want = _np_roi_align(feat[int(roi[0])], roi, 3, 3, 0.5, 2)
        np.testing.assert_allclose(got[i], want, rtol=1e-4, atol=1e-5)


def test_roi_align_backward_numeric():
    rng = np.random.RandomState(2)
    feat = rng.randn(1, 2, 6, 6).astype(np.float32)
    rois = np.array([[0, 0.0, 0.0, 5.0, 5.0]], np.float32)
    x = nd.array(feat)
    x.attach_grad()
    cot = rng.randn(1, 2, 2, 2).astype(np.float32)
    with mx.autograd.record():
        out = nd.contrib.ROIAlign(x, nd.array(rois), pooled_size=(2, 2),
                                  spatial_scale=1.0, sample_ratio=2)
        loss = (out * nd.array(cot)).sum()
    loss.backward()
    g = x.grad.asnumpy()
    # numeric gradient on a few random entries
    eps = 1e-2
    for _ in range(5):
        ci, yi, xi = (rng.randint(2), rng.randint(6), rng.randint(6))
        fp = feat.copy(); fp[0, ci, yi, xi] += eps
        fm = feat.copy(); fm[0, ci, yi, xi] -= eps
        op = nd.contrib.ROIAlign(nd.array(fp), nd.array(rois),
                                 pooled_size=(2, 2), spatial_scale=1.0,
                                 sample_ratio=2).asnumpy()
        om = nd.contrib.ROIAlign(nd.array(fm), nd.array(rois),
                                 pooled_size=(2, 2), spatial_scale=1.0,
                                 sample_ratio=2).asnumpy()
        num = ((op - om) / (2 * eps) * cot).sum()
        np.testing.assert_allclose(g[0, ci, yi, xi], num, rtol=1e-2,
                                   atol=1e-3)


def test_roi_pooling_max_semantics():
    feat = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)
    out = nd.ROIPooling(nd.array(feat), nd.array(rois),
                        pooled_size=(2, 2), spatial_scale=1.0).asnumpy()
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])


def test_bipartite_matching_greedy():
    score = np.array([[[0.5, 0.6], [0.9, 0.2], [0.3, 0.1]]], np.float32)
    rm, cm = nd.contrib.bipartite_matching(nd.array(score), threshold=0.1)
    np.testing.assert_allclose(rm.asnumpy(), [[1, 0, -1]])
    np.testing.assert_allclose(cm.asnumpy(), [[1, 0]])
    # threshold excludes weak pairs
    rm, cm = nd.contrib.bipartite_matching(nd.array(score), threshold=0.7)
    np.testing.assert_allclose(rm.asnumpy(), [[-1, 0, -1]])
    np.testing.assert_allclose(cm.asnumpy(), [[1, -1]])


def test_box_nms_symbolic():
    import mxnet_tpu.symbol as sym
    data = sym.Variable("data")
    out = sym.contrib.box_nms(data, overlap_thresh=0.5, coord_start=1,
                              score_index=0)
    arr = np.array([[[0.9, 0, 0, 1, 1],
                     [0.8, 0, 0, .9, .9]]], np.float32)
    ex = out.bind(mx.cpu(), {"data": nd.array(arr)})
    res = ex.forward()[0].asnumpy()
    assert res[0, 0, 0] == pytest.approx(0.9)
    assert res[0, 1, 0] == -1.0


def test_proposal_basic():
    """RPN Proposal: decoded/clipped boxes, NMS, cyclic padding
    (ref proposal.cc:316-414)."""
    rng = np.random.RandomState(9)
    a = 9  # 3 scales x 3 ratios
    h = w = 4
    cls_prob = rng.uniform(0, 1, (1, 2 * a, h, w)).astype(np.float32)
    bbox_pred = (rng.randn(1, 4 * a, h, w) * 0.1).astype(np.float32)
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    rois = nd.contrib.Proposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        feature_stride=16, scales=(4, 8, 16), ratios=(0.5, 1, 2),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=12, threshold=0.7,
        rpn_min_size=4).asnumpy()
    assert rois.shape == (12, 5)
    assert (rois[:, 0] == 0).all()                # batch index
    x1, y1, x2, y2 = rois[:, 1], rois[:, 2], rois[:, 3], rois[:, 4]
    assert (x1 >= 0).all() and (x2 <= 63).all()   # clipped to image
    assert (y1 >= 0).all() and (y2 <= 63).all()
    assert ((x2 - x1 + 1) >= 4).all()             # min-size filter


def test_proposal_output_score_and_batch():
    rng = np.random.RandomState(10)
    a = 3  # 3 ratios x 1 scale
    cls_prob = rng.uniform(0, 1, (2, 2 * a, 3, 3)).astype(np.float32)
    bbox_pred = np.zeros((2, 4 * a, 3, 3), np.float32)
    im_info = np.tile(np.array([48.0, 48.0, 1.0], np.float32), (2, 1))
    rois, scores = nd.contrib.MultiProposal(
        nd.array(cls_prob), nd.array(bbox_pred), nd.array(im_info),
        feature_stride=16, scales=(8,), ratios=(0.5, 1, 2),
        rpn_pre_nms_top_n=20, rpn_post_nms_top_n=5, output_score=True)
    rois = rois.asnumpy()
    scores = scores.asnumpy()
    assert rois.shape == (10, 5) and scores.shape == (10, 1)
    np.testing.assert_array_equal(rois[:5, 0], 0)
    np.testing.assert_array_equal(rois[5:, 0], 1)
    # scores sorted desc within each image (pre-NMS order preserved)
    assert scores[0, 0] >= scores[1, 0]
